"""stdlib utilities (≙ each package's _test.pony: promises, time,
random, logger)."""

import io
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.stdlib import logger as L
from ponyc_tpu.stdlib import random as R
from ponyc_tpu.stdlib.promises import (Custodian, Promise, PromiseRejected,
                                       join, select)
from ponyc_tpu.stdlib.timers import Timers


# ---- promises (≙ packages/promises/_test.pony) ----

def test_promise_fulfil_and_chain():
    p = Promise()
    seen = []
    p.next(lambda v: v * 2).next(seen.append)
    p.fulfil(21)
    assert seen == [42]
    assert p.value() == 21
    p.fulfil(99)                      # write-once
    assert p.value() == 21


def test_promise_reject_propagates():
    p = Promise()
    errs = []
    p.next(lambda v: v, rejected=errs.append)
    p.reject("nope")
    assert errs == ["nope"]
    with pytest.raises(PromiseRejected):
        p.value()


def test_promise_chain_after_resolution():
    p = Promise().fulfil(5)
    got = []
    p.next(got.append)
    assert got == [5]


def test_join_and_select():
    ps = [Promise() for _ in range(3)]
    j = join(ps)
    s = select([Promise(), Promise()])
    for i, p in enumerate(ps):
        p.fulfil(i)
    assert j.value() == [0, 1, 2]
    s_src = select([Promise().fulfil("first"), Promise()])
    assert s_src.value() == "first"
    assert not s.done()


def test_promise_fulfilled_by_actor_program():
    @actor
    class Summer:
        HOST = True
        total: I32

        @behaviour
        def add(self, st, x: I32):
            t = st["total"] + x
            if t >= 6:
                self.rt._test_promise.fulfil(t)
            return {**st, "total": t}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=4, max_sends=1,
                                msg_words=2, inject_slots=16))
    rt.declare(Summer, 1).start()
    a = rt.spawn(Summer)
    p = Promise(rt)
    rt._test_promise = p
    for x in (1, 2, 3):
        rt.send(a, Summer.add, x)
    assert p.value(timeout=30) == 6


def test_custodian_disposes_everything():
    class D:
        def __init__(self):
            self.closed = False

        def dispose(self):
            self.closed = True

    c = Custodian()
    ds = [D(), D()]
    for d in ds:
        c.apply(d)
    c.dispose()
    assert all(d.closed for d in ds)


# ---- random (≙ packages/random/_test.pony) ----

def test_device_random_is_deterministic_and_spread():
    ids = jnp.arange(1024, dtype=jnp.int32)
    u1 = jax.vmap(lambda a: R.uniform(a, 7))(ids)
    u2 = jax.vmap(lambda a: R.uniform(a, 7))(ids)
    assert np.allclose(u1, u2)               # counter-based: reproducible
    u3 = jax.vmap(lambda a: R.uniform(a, 8))(ids)
    assert not np.allclose(u1, u3)           # new step → new draws
    arr = np.asarray(u1)
    assert 0.0 <= arr.min() and arr.max() < 1.0
    assert 0.4 < arr.mean() < 0.6            # roughly uniform
    k = np.asarray(jax.vmap(lambda a: R.randint(a, 3, 10, 20))(ids))
    assert k.min() >= 10 and k.max() < 20 and len(np.unique(k)) == 10


def test_host_rand_api():
    r = R.Rand(seed=123)
    xs = [r.int(100) for _ in range(50)]
    assert all(0 <= x < 100 for x in xs)
    assert len(set(xs)) > 20
    assert 0.0 <= r.real() < 1.0
    lst = list(range(10))
    R.Rand(seed=5).shuffle(lst)
    assert sorted(lst) == list(range(10)) and lst != list(range(10))


# ---- timers (≙ packages/time/_test.pony) ----

@actor
class Ticker:
    HOST = True
    ticks: I32

    @behaviour
    def tick(self, st, kind: I32, arg: I32, flags: I32):
        t = st["ticks"] + arg
        self.exit(0, when=t >= 3)
        return {**st, "ticks": t}


def test_count_limited_timer_stops_itself():
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=4, max_sends=1,
                                msg_words=3, inject_slots=16))
    rt.declare(Ticker, 1).start()
    a = rt.spawn(Ticker)
    timers = Timers(rt)
    timers.timer(a, Ticker.tick, 0.01, count=3)
    code = rt.run(max_steps=20000)
    assert code == 0
    assert rt.state_of(a)["ticks"] == 3
    time.sleep(0.05)                  # were it still live, more would queue
    assert not timers._live
    timers.dispose()
    rt.stop()


def test_after_fires_once():
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=4, max_sends=1,
                                msg_words=3, inject_slots=16))
    rt.declare(Ticker, 1).start()
    a = rt.spawn(Ticker)
    timers = Timers(rt)
    t0 = time.time()
    timers.after(a, Ticker.tick, 0.05)
    rt.run(max_steps=20000)
    assert rt.state_of(a)["ticks"] == 1
    assert time.time() - t0 >= 0.04
    timers.dispose()
    rt.stop()


# ---- logger (≙ packages/logger/_test.pony) ----

def test_logger_gating_and_sink():
    out = io.StringIO()
    log = L.Logger(L.WARN, out=out)
    assert not log(L.INFO)
    assert log(L.ERROR)
    assert not log.info("hidden")
    assert log.warn("visible")
    assert log.error("bad")
    text = out.getvalue()
    assert "hidden" not in text
    assert "WARN" in text and "visible" in text and "bad" in text
