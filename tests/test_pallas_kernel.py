"""Mailbox-drain Pallas kernel (ops/mailbox_kernel.py) — correctness
against the XLA select-chain path, and the full engine running with
opts.pallas=True (interpret mode on CPU, ≙ the reference exercising
codegen'd dispatch through its JIT harness, genjit.cc)."""

import jax.numpy as jnp
import numpy as np

from ponyc_tpu import Runtime, RuntimeOptions
from ponyc_tpu.models import ring, ubench
from ponyc_tpu.ops import mailbox_kernel as mk


def test_drain_matches_reference():
    rng = np.random.default_rng(0)
    cap, w1, n, batch = 8, 3, 256, 4
    buf = jnp.asarray(rng.integers(-5, 100, (cap, w1, n)), jnp.int32)
    head = jnp.asarray(rng.integers(0, 1000, (n,)), jnp.int32)
    occ = rng.integers(0, cap + 1, (n,))
    n_run = jnp.asarray(np.minimum(occ, batch), jnp.int32)

    msgs, valids = mk.drain_msgs(buf, head, n_run, batch=batch,
                                 interpret=True)
    # Oracle: slot (head+k) % cap per actor, valid while k < n_run.
    b_np, h_np = np.asarray(buf), np.asarray(head)
    for k in range(batch):
        slot = (h_np + k) % cap
        want = b_np[slot, :, np.arange(n)].T          # [w1, n]
        np.testing.assert_array_equal(np.asarray(msgs[k]), want)
        np.testing.assert_array_equal(np.asarray(valids[k]),
                                      np.asarray(n_run) > k)


def test_drain_multiblock_grid():
    # n > LANE_BLOCK exercises the grid dimension.
    cap, w1, batch = 4, 2, 2
    n = 2 * mk.LANE_BLOCK
    buf = jnp.arange(cap * w1 * n, dtype=jnp.int32).reshape(cap, w1, n)
    head = jnp.arange(n, dtype=jnp.int32) % cap
    n_run = jnp.full((n,), batch, jnp.int32)
    msgs, valids = mk.drain_msgs(buf, head, n_run, batch=batch,
                                 interpret=True)
    b_np, h_np = np.asarray(buf), np.asarray(head)
    for k in range(batch):
        slot = (h_np + k) % cap
        want = b_np[slot, :, np.arange(n)].T
        np.testing.assert_array_equal(np.asarray(msgs[k]), want)
    assert bool(np.asarray(valids).all())


def test_engine_runs_on_pallas_path():
    # Same program, pallas on vs off: identical results and counters.
    opts_p = RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1,
                            msg_words=1, spill_cap=128, inject_slots=8,
                            pallas=True)
    rt = ring.run(n_nodes=128, hops=300, opts=opts_p)
    st = rt.cohort_state(ring.RingNode)
    assert st["passes"].sum() == 300

    counts = {}
    for pal in (False, True):
        rt2, ids = ubench.build(256, RuntimeOptions(
            mailbox_cap=4, batch=1, max_sends=1, msg_words=1,
            spill_cap=128, inject_slots=8, pallas=pal))
        ubench.seed_all(rt2, ids, hops=8)
        rt2.run(max_steps=64)
        counts[pal] = rt2.counter("n_processed")
    assert counts[True] == counts[False] > 0
