"""Order-SENSITIVE differential testing: per-edge FIFO (causal order).

The Pony guarantee under test: messages from sender A to receiver B are
dispatched in the order A sent them (messageq FIFO,
reference src/libponyrt/actor/messageq.c:102-160). The commutative
differential suite (test_differential.py) cannot see an ordering
violation by design; this file can see a SINGLE one.

Method: every producer stamps each message with a per-edge sequence
number; every consumer checks ON DEVICE that each in-edge's stamps
arrive exactly contiguous (seq == last_seen + 1) and counts violations.
The per-edge oracle sequence is 0,1,2,… by construction, so
`violations == 0` + `last_seen == n-1` IS the exact oracle comparison —
any inversion, duplication, or loss anywhere in delivery (plan/cosort),
the device spill retry, the route-spill retry, or the aged-unmute
release window trips it.

Configs deliberately aim at the reordering windows SURVEY §7 hard part
(c) names: tiny caps (device-spill retry), 4-shard mesh with a tiny
route bucket (route-spill retry), aggressive mute aging (aged-unmute
release), both delivery formulations, and the fused Pallas kernel.
"""

import numpy as np
import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour

IN_SLOTS = 4          # in-edges tracked per consumer (fixed-width state)


@actor
class Cons:
    """Consumer with IN_SLOTS tracked in-edges: asserts per-edge stamps
    arrive contiguous; `bad` counts every FIFO violation."""
    last0: I32
    last1: I32
    last2: I32
    last3: I32
    bad: I32
    got: I32

    BATCH = 1          # slow consumer → overload → mute machinery engages

    @behaviour
    def consume(self, st, slot: I32, seq: I32):
        upd = {"bad": st["bad"], "got": st["got"] + 1}
        for s in range(IN_SLOTS):
            is_s = slot == s
            last = st[f"last{s}"]
            viol = is_s & (seq != last + 1)
            upd["bad"] = upd["bad"] + np.int32(1) * viol
            upd[f"last{s}"] = last + (seq - last) * is_s
        return {**st, **upd}


@actor
class Prod:
    """Producer streaming to two fixed (consumer, slot) edges, one stamp
    per tick via a self-send chain (so its own mailbox also carries a
    FIFO-critical stream: the self-edge n,n-1,… chain)."""
    c1: Ref["Cons"]
    c2: Ref["Cons"]
    slot1: I32
    slot2: I32
    seq: I32

    MAX_SENDS = 3

    @behaviour
    def produce(self, st, n: I32):
        self.send(st["c1"], Cons.consume, st["slot1"], st["seq"], when=n > 0)
        self.send(st["c2"], Cons.consume, st["slot2"], st["seq"], when=n > 0)
        self.send(self.actor_id, Prod.produce, n - 1, when=n > 0)
        return {**st, "seq": st["seq"] + (n > 0) * np.int32(1)}


def _wire(seed, n_cons):
    """Random bipartite wiring: every consumer gets exactly IN_SLOTS
    in-edges, every producer exactly two out-edges (a producer may draw
    two slots of the SAME consumer — two edges into one mailbox)."""
    rng = np.random.default_rng(seed)
    pairs = [(c, s) for c in range(n_cons) for s in range(IN_SLOTS)]
    rng.shuffle(pairs)
    n_prod = len(pairs) // 2
    return n_prod, pairs[:n_prod], pairs[n_prod:]


def run_fifo(seed, okw, n_cons=6, items=60):
    n_prod, e1, e2 = _wire(seed, n_cons)
    opts = RuntimeOptions(msg_words=2, **okw)
    rt = Runtime(opts)
    rt.declare(Prod, n_prod).declare(Cons, n_cons)
    rt.start()
    cids = rt.spawn_many(Cons, n_cons,
                         last0=np.full(n_cons, -1, np.int32),
                         last1=np.full(n_cons, -1, np.int32),
                         last2=np.full(n_cons, -1, np.int32),
                         last3=np.full(n_cons, -1, np.int32))
    pids = rt.spawn_many(Prod, n_prod,
                         c1=cids[np.asarray([c for c, _ in e1])],
                         c2=cids[np.asarray([c for c, _ in e2])],
                         slot1=np.asarray([s for _, s in e1], np.int32),
                         slot2=np.asarray([s for _, s in e2], np.int32))
    rt.bulk_send(pids, Prod.produce, np.full(n_prod, items, np.int32))
    assert rt.run(max_steps=500_000) == 0, "must quiesce"
    st = rt.cohort_state(Cons)
    bad = st["bad"][:n_cons]
    assert not bad.any(), f"FIFO violations: {np.asarray(bad)}"
    # Completeness: every edge delivered its full stream (the per-slot
    # last stamp is exactly items-1, matching the oracle sequence).
    for s in range(IN_SLOTS):
        last = np.asarray(st[f"last{s}"][:n_cons])
        assert (last == items - 1).all(), (s, last)
    got = np.asarray(st["got"][:n_cons])
    assert (got == IN_SLOTS * items).all(), got
    # Producer self-chains all ran to exhaustion.
    pst = rt.cohort_state(Prod)
    assert (np.asarray(pst["seq"][:n_prod]) == items).all()
    return rt


CONFIGS = [
    ("tiny-cap-dspill", dict(mailbox_cap=2, batch=1, max_sends=3,
                             spill_cap=2048, inject_slots=16)),
    ("cosort", dict(mailbox_cap=4, batch=2, max_sends=3, spill_cap=2048,
                    inject_slots=16, delivery="cosort")),
    ("aged-unmute", dict(mailbox_cap=2, batch=1, max_sends=3,
                         spill_cap=2048, inject_slots=16,
                         mute_age_limit=2)),
    ("mesh4-route-spill", dict(mailbox_cap=2, batch=1, max_sends=3,
                               spill_cap=4096, inject_slots=32,
                               mesh_shards=4, route_bucket=8,
                               quiesce_interval=2)),
    ("fused-kernel", dict(mailbox_cap=4, batch=2, max_sends=3,
                          spill_cap=2048, inject_slots=16,
                          pallas_fused=True)),
    # PR 11: persistent fused-window megakernel (ops/megakernel.py);
    # the per-edge FIFO guarantee must survive the kernel boundary's
    # int16+escape record packing bit-for-bit.
    ("pallas-mega", dict(mailbox_cap=2, batch=1, max_sends=3,
                         spill_cap=2048, inject_slots=16,
                         delivery="pallas_mega")),
]


@pytest.mark.parametrize("name,okw", CONFIGS, ids=[c[0] for c in CONFIGS])
def test_per_edge_fifo(name, okw):
    run_fifo(seed=101, okw=okw)


def test_per_edge_fifo_more_seeds_tiny():
    for seed in (202, 303):
        run_fifo(seed, CONFIGS[0][1], n_cons=4, items=40)


def test_detector_catches_single_inversion():
    """Sensitivity proof: an artificially inverted pair of stamps on one
    edge MUST trip the violation counter — the detector is not
    vacuously green."""
    opts = RuntimeOptions(mailbox_cap=8, batch=1, msg_words=2,
                          max_sends=3, spill_cap=64, inject_slots=8)
    rt = Runtime(opts)
    rt.declare(Prod, 1).declare(Cons, 1)
    rt.start()
    c = rt.spawn(Cons, last0=-1, last1=-1, last2=-1, last3=-1)
    rt.spawn(Prod)
    rt.send(c, Cons.consume, 0, 1)     # seq 1 first — inverted
    rt.send(c, Cons.consume, 0, 0)     # then seq 0
    rt.run(max_steps=1000)
    assert rt.state_of(c)["bad"] > 0, \
        "inverted stamps did not trip the FIFO detector"


def test_host_consumer_fifo():
    """The SAME per-edge streams terminating in a HOST actor: the
    device→host out-ring drain must preserve per-edge order too (the
    ASIO-side half of the FIFO claim). The host log records real arrival
    order; each edge's subsequence must equal 0,1,2,… exactly."""
    logs = {}

    @actor
    class HCons:
        HOST = True
        got: I32

        @behaviour
        def consume(self, st, edge: I32, seq: I32):
            logs.setdefault(int(edge), []).append(int(seq))
            return {**st, "got": st["got"] + 1}

    n_prod, items = 6, 40

    @actor
    class HProd:
        sink: Ref["HCons"]
        edge: I32
        seq: I32

        MAX_SENDS = 2

        @behaviour
        def produce(self, st, n: I32):
            self.send(st["sink"], HCons.consume, st["edge"], st["seq"],
                      when=n > 0)
            self.send(self.actor_id, HProd.produce, n - 1, when=n > 0)
            return {**st, "seq": st["seq"] + (n > 0) * np.int32(1)}

    opts = RuntimeOptions(mailbox_cap=2, batch=1, msg_words=2, max_sends=2,
                          spill_cap=2048, inject_slots=16,
                          host_out_slots=8)   # tiny out-ring → drain churn
    rt = Runtime(opts)
    rt.declare(HProd, n_prod).declare(HCons, 1)
    rt.start()
    sink = rt.spawn(HCons)
    pids = rt.spawn_many(HProd, n_prod, sink=np.full(n_prod, sink),
                         edge=np.arange(n_prod, dtype=np.int32))
    rt.bulk_send(pids, HProd.produce, np.full(n_prod, items, np.int32))
    assert rt.run(max_steps=200_000) == 0
    assert rt.state_of(sink)["got"] == n_prod * items
    for e in range(n_prod):
        assert logs.get(e) == list(range(items)), (e, (logs.get(e)
                                                       or [])[:10])


# --- blob payload↔message binding under order stress -------------------
# The commutative blob differential cannot see a PAYLOAD SWAP between
# two in-flight messages (the multiset of values survives); here every
# message carries its sequence stamp BOTH in a payload word and inside
# its blob, and the consumer checks on device that (a) per-edge stamps
# stay contiguous (FIFO) and (b) blob stamp == word stamp (binding) —
# through tiny-cap spills and, on a mesh, through migration.

@actor
class BlobProd:
    c1: "Ref[BlobCons]"
    slot1: I32
    seq: I32

    MAX_SENDS = 2
    MAX_BLOBS = 1
    BLOB_DISPATCHES = 1
    BATCH = 1

    @behaviour
    def produce(self, st, n: I32):
        from ponyc_tpu import Blob  # noqa: F401
        go = n > 0
        h = self.blob_alloc(length=2, when=go)
        self.blob_set(h, 0, st["seq"], when=go)
        self.blob_set(h, 1, self.actor_id, when=go)
        self.send(st["c1"], BlobCons.consume, st["slot1"], st["seq"], h,
                  when=go)
        self.send(self.actor_id, BlobProd.produce, n - 1, when=n > 1)
        return {**st, "seq": st["seq"] + (n > 0) * np.int32(1)}


@actor
class BlobCons:
    last0: I32
    last1: I32
    got: I32
    bad: I32          # FIFO violations (stamp not contiguous per edge)
    badbind: I32      # payload/message binding violations

    @behaviour
    def consume(self, st, slot: I32, seq: I32, h: "Blob"):
        bseq = self.blob_get(h, 0)
        self.blob_free(h)
        upd = dict(st)
        upd["badbind"] = st["badbind"] + (bseq != seq)
        viol = np.int32(0)
        for s in range(2):
            is_s = slot == s
            last = st[f"last{s}"]
            upd[f"last{s}"] = last + (seq - last) * is_s
        viol = sum((slot == s) & (seq != st[f"last{s}"] + 1)
                   for s in range(2))
        upd["bad"] = st["bad"] + viol
        upd["got"] = st["got"] + 1
        return upd


def run_blob_fifo(seed, okw, n_cons=4, items=30):
    rng = np.random.default_rng(seed)
    n_prod = 2 * n_cons                  # exactly two edges per consumer
    perm = rng.permutation(n_prod)
    cons_of = np.repeat(np.arange(n_cons), 2)[perm]
    slot_of = np.tile(np.arange(2), n_cons)[perm]
    opts = RuntimeOptions(msg_words=3,
                          blob_slots=max(256, n_prod * items),
                          blob_words=2, **okw)
    rt = Runtime(opts)
    rt.declare(BlobProd, n_prod).declare(BlobCons, n_cons)
    rt.start()
    cids = rt.spawn_many(BlobCons, n_cons,
                         last0=np.full(n_cons, -1, np.int32),
                         last1=np.full(n_cons, -1, np.int32))
    pids = rt.spawn_many(BlobProd, n_prod,
                         c1=cids[cons_of], slot1=slot_of.astype(np.int32))
    rt.bulk_send(pids, BlobProd.produce, np.full(n_prod, items, np.int32))
    assert rt.run(max_steps=500_000) == 0, "must quiesce"
    st = rt.cohort_state(BlobCons)
    assert not np.asarray(st["badbind"][:n_cons]).any(), (
        "payload/message binding violated", np.asarray(st["badbind"]))
    assert not np.asarray(st["bad"][:n_cons]).any(), (
        "FIFO violated", np.asarray(st["bad"]))
    for s in range(2):
        assert (np.asarray(st[f"last{s}"][:n_cons]) == items - 1).all()
    assert (np.asarray(st["got"][:n_cons]) == 2 * items).all()
    assert rt.blobs_in_use == 0
    return rt


@pytest.mark.parametrize("name,okw", [
    ("tiny", dict(mailbox_cap=2, batch=1, max_sends=2, spill_cap=2048,
                  inject_slots=16)),
    ("cosort", dict(mailbox_cap=4, batch=2, max_sends=2, spill_cap=2048,
                    inject_slots=16, delivery="cosort")),
    ("mesh4-bucket", dict(mailbox_cap=2, batch=1, max_sends=2,
                          spill_cap=4096, inject_slots=32, mesh_shards=4,
                          route_bucket=4, quiesce_interval=2)),
])
def test_blob_payload_binding_fifo(name, okw):
    run_blob_fifo(11, okw)
