"""Interactive terminal input (stdlib/term.py: ANSITerm + Readline).

≙ packages/term/ansi_term.pony (escape state machine over stdin bytes),
readline.pony (line editing, history, tab completion, promise-driven
prompts), readline_notify.pony — plus the bridge stdin wiring
(lang/stdfd.c's role). Tests feed bytes directly (the same entry the
stdin fd subscription calls)."""

import io

from ponyc_tpu.stdlib.term import (ANSINotify, ANSITerm, Readline,
                                   ReadlineNotify)


class KeyLog(ANSINotify):
    def __init__(self):
        self.events = []

    def apply(self, term, byte):
        self.events.append(("byte", byte))

    def up(self, ctrl=False, alt=False, shift=False):
        self.events.append(("up", ctrl, alt, shift))

    def down(self, ctrl=False, alt=False, shift=False):
        self.events.append(("down", ctrl, alt, shift))

    def left(self, ctrl=False, alt=False, shift=False):
        self.events.append(("left", ctrl, alt, shift))

    def right(self, ctrl=False, alt=False, shift=False):
        self.events.append(("right", ctrl, alt, shift))

    def delete(self, ctrl=False, alt=False, shift=False):
        self.events.append(("delete", ctrl, alt, shift))

    def home(self, ctrl=False, alt=False, shift=False):
        self.events.append(("home", ctrl, alt, shift))

    def end_key(self, ctrl=False, alt=False, shift=False):
        self.events.append(("end", ctrl, alt, shift))

    def page_up(self, ctrl=False, alt=False, shift=False):
        self.events.append(("pgup", ctrl, alt, shift))

    def fn_key(self, i, ctrl=False, alt=False, shift=False):
        self.events.append(("fn", i, ctrl, alt, shift))

    def size(self, rows, cols):
        self.events.append(("size", rows > 0, cols > 0))

    def closed(self):
        self.events.append(("closed",))


def test_escape_state_machine_parses_standard_keys():
    log = KeyLog()
    term = ANSITerm(log)
    log.events.clear()                       # drop the initial size()
    term.apply(b"a")                         # plain byte
    term.apply(b"\x1b[A")                    # CSI up
    term.apply(b"\x1b[1;5C")                 # ctrl-right (mod 5 = 1+4)
    term.apply(b"\x1b[3~")                   # delete
    term.apply(b"\x1b[5~")                   # page up
    term.apply(b"\x1b[15~")                  # F5
    term.apply(b"\x1bOD")                    # SS3 left
    term.apply(b"\x1bOP")                    # SS3 PF1 = F1
    assert log.events == [
        ("byte", ord("a")),
        ("up", False, False, False),
        ("right", True, False, False),
        ("delete", False, False, False),
        ("pgup", False, False, False),
        ("fn", 5, False, False, False),
        ("left", False, False, False),
        ("fn", 1, False, False, False),
    ]


def test_split_escape_sequences_across_reads():
    """A CSI sequence arriving one byte per read must parse the same
    (partial reads are normal on a pty)."""
    log = KeyLog()
    term = ANSITerm(log)
    log.events.clear()
    for b in b"\x1b", b"[", b"1", b";", b"2", b"A":
        term.apply(b)
    assert log.events == [("up", False, False, True)]     # shift-up


def test_bare_escape_passes_through():
    log = KeyLog()
    term = ANSITerm(log)
    log.events.clear()
    term.apply(b"\x1bq")                     # ESC then plain byte
    assert log.events == [("byte", 0x1B), ("byte", ord("q"))]


class LineSink(ReadlineNotify):
    def __init__(self, completions=()):
        self.lines = []
        self.completions = list(completions)
        self.reject_after = None

    def apply(self, line, prompt):
        self.lines.append(line)
        if self.reject_after is not None and len(
                self.lines) >= self.reject_after:
            prompt.reject("done")
        else:
            prompt.fulfil("> ")

    def tab(self, line):
        return [c for c in self.completions if c.startswith(line)]


def _readline(completions=()):
    sink = LineSink(completions)
    out = io.StringIO()
    rl = Readline(sink, out)
    term = ANSITerm(rl, out)
    term.prompt("> ")                        # unblock with first prompt
    return sink, out, rl, term


def test_readline_basic_line_dispatch_and_echo():
    sink, out, rl, term = _readline()
    term.apply(b"hello\n")
    assert sink.lines == ["hello"]
    assert "hello" in out.getvalue()
    term.apply(b"world\r")                   # CR dispatches too
    assert sink.lines == ["hello", "world"]


def test_readline_editing_keys():
    sink, out, rl, term = _readline()
    term.apply(b"helo")
    term.apply(b"\x1b[D")                    # left (cursor at 'o')
    term.apply(b"l")                         # insert -> "hello"
    term.apply(b"\x01")                      # ctrl-a home
    term.apply(b"X")                         # insert at start
    term.apply(b"\x7f")                      # backspace removes X
    term.apply(b"\x05")                      # ctrl-e end
    term.apply(b"\n")
    assert sink.lines == ["hello"]


def test_readline_history_navigation():
    sink, out, rl, term = _readline()
    term.apply(b"first\n")
    term.apply(b"second\n")
    term.apply(b"\x1b[A")                    # up -> "second"
    term.apply(b"\n")
    assert sink.lines == ["first", "second", "second"]
    term.apply(b"\x1b[A\x1b[A\x1b[A")        # up to the oldest
    term.apply(b"\n")
    assert sink.lines[-1] == "first"


def test_readline_tab_completion():
    sink, out, rl, term = _readline(["commit", "checkout"])
    term.apply(b"com\t")                     # unique -> completes
    term.apply(b"\n")
    assert sink.lines == ["commit"]
    term.apply(b"c\t")                       # ambiguous -> listed
    assert "commit" in out.getvalue() and "checkout" in out.getvalue()
    term.apply(b"heckout\n")                 # keep typing after listing
    assert sink.lines[-1] == "checkout"


def test_readline_ctrl_d_on_empty_line_closes():
    sink, out, rl, term = _readline()
    term.apply(b"\x04")                      # ctrl-d, empty edit
    assert term.closed


def test_readline_rejected_prompt_closes_terminal():
    sink, out, rl, term = _readline()
    sink.reject_after = 1
    term.apply(b"quit\n")
    assert term.closed


def test_readline_history_persistence(tmp_path):
    path = str(tmp_path / "history")
    sink = LineSink()
    out = io.StringIO()
    rl = Readline(sink, out, path=path, maxlen=2)
    term = ANSITerm(rl, out)
    term.prompt("> ")
    term.apply(b"one\ntwo\nthree\n")
    term.dispose()                           # saves history
    with open(path) as f:
        assert f.read().splitlines() == ["two", "three"]   # maxlen=2
    rl2 = Readline(LineSink(), io.StringIO(), path=path, maxlen=2)
    assert rl2._history == ["two", "three"]


def test_readline_utf8_multibyte_input():
    """Multi-byte UTF-8 arrives byte-at-a-time and must insert ONE
    character with correct cursor math."""
    sink, out, rl, term = _readline()
    term.apply("café".encode("utf-8"))       # é = 2 bytes
    term.apply(b"\x7f")                      # backspace removes é (1 ch)
    term.apply("é!".encode("utf-8"))
    term.apply(b"\n")
    assert sink.lines == ["café!"]


def test_dispose_hooks_run_on_every_close_path():
    calls = []
    sink, out, rl, term = _readline()
    term.add_dispose_hook(lambda: calls.append("hook"))
    term.apply(b"\x04")                      # ctrl-d on empty line
    assert term.closed and calls == ["hook"]
    term.dispose()                           # idempotent
    assert calls == ["hook"]


def test_readline_blocked_until_prompt():
    sink = LineSink()
    out = io.StringIO()
    rl = Readline(sink, out)
    term = ANSITerm(rl, out)
    term.apply(b"ignored\n")                 # no prompt yet: blocked
    assert sink.lines == []
    term.prompt("> ")
    term.apply(b"seen\n")
    assert sink.lines == ["seen"]
