"""The test/bench harnesses themselves (≙ ponytest's own _test.pony and
ponybench's examples)."""

import io

import jax.numpy as jnp

from ponyc_tpu.benching import BenchRunner
from ponyc_tpu.testing import TestHelper, TestRunner, UnitTest


class _Pass(UnitTest):
    name = "sample/pass"

    def apply(self, h):
        h.assert_eq(2 + 2, 4)
        h.assert_true(True)


class _Fail(UnitTest):
    name = "sample/fail"

    def apply(self, h):
        h.log("some context")
        h.assert_eq(1, 2, "intentional")


class _ExpectFail(UnitTest):
    name = "sample/expect-fail"
    expect_failure = True

    def apply(self, h):
        h.fail("supposed to fail")


class _Raises(UnitTest):
    name = "sample/raises"

    def apply(self, h):
        h.assert_error(lambda: (_ for _ in ()).throw(ValueError()))


class _TimesOut(UnitTest):
    name = "sample/timeout"
    timeout = 0.2

    def apply(self, h):
        import time
        time.sleep(5)


class _ActorProgram(UnitTest):
    """A real runtime-driven test — the intended usage (≙ stdlib tests
    running whole actor programs under ponytest)."""
    name = "actor/ring"

    def apply(self, h):
        from ponyc_tpu import RuntimeOptions
        from ponyc_tpu.models import ring
        rt = ring.run(n_nodes=8, hops=16,
                      opts=RuntimeOptions(mailbox_cap=8, batch=1,
                                          max_sends=1, msg_words=1))
        st = rt.cohort_state(ring.RingNode)
        h.assert_eq(int(st["passes"].sum()), 16)


def test_runner_semantics():
    out = io.StringIO()
    finished = []
    r = TestRunner(out=out, tests_finished=finished.append)
    for t in (_Pass(), _Fail(), _ExpectFail(), _Raises(), _TimesOut()):
        r.add(t)
    ok = r.run()
    assert not ok
    by = {x.name: x for x in r.results}
    assert by["sample/pass"].ok
    assert not by["sample/fail"].ok
    assert "intentional" in " ".join(by["sample/fail"].failures)
    assert "some context" in by["sample/fail"].logs
    assert by["sample/expect-fail"].ok
    assert by["sample/raises"].ok
    assert by["sample/timeout"].timed_out and not by["sample/timeout"].ok
    assert len(finished) == 1 and len(finished[0]) == 5
    text = out.getvalue()
    assert "5 test(s) ran: 3 ok, 2 failed" in text


def test_runner_filters():
    out = io.StringIO()
    r = TestRunner(out=out)
    r.add(_Pass()).add(_Fail())
    assert r.run(only="sample/pass")
    assert len(r.results) == 1
    assert r.run(only="sample/*", exclude="sample/fail")


def test_actor_program_under_harness():
    out = io.StringIO()
    assert TestRunner(out=out).add(_ActorProgram()).run()


def test_bench_runner_scales_and_reports():
    out = io.StringIO()
    b = BenchRunner(min_window_s=0.02, out=out)
    x = jnp.arange(4096, dtype=jnp.float32)
    import jax
    f = jax.jit(lambda v: (v * 2.0).sum())
    r = b.bench("double-sum", f, x, items_per_call=x.size)
    assert r.reps >= 1 and r.mean_s > 0
    assert r.ops_per_s > 0
    b.report()
    b.report(json_lines=True)
    text = out.getvalue()
    assert "double-sum" in text and "ops_per_s" in text
