"""Actor GC: reachability tracing ≙ ORCA rc + cycle detector.

The reference collects an actor when it is blocked with rc 0
(gc/gc.c, actor.c:528-544) and collects *cycles* of blocked actors via
the cycle-detector actor (gc/cycle.c:345-651). Here both are one
parallel trace (runtime/gc.py); these tests pin down the same
observable semantics: unreachable+quiet ⇒ collected, reachable or
message-holding ⇒ kept, cycles ⇒ collected, host refs ⇒ roots.
"""

import numpy as np

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour


@actor
class Node:
    next_ref: Ref
    hits: I32

    @behaviour
    def link(self, st, to: Ref):
        return {**st, "next_ref": to}

    @behaviour
    def poke(self, st):
        return {**st, "hits": st["hits"] + 1}

    @behaviour
    def forward(self, st, to: Ref):
        # Holds a ref in a *message* to itself, not in any state field.
        self.send(self.actor_id, Node.forward_sink, to, when=False)
        return st

    @behaviour
    def forward_sink(self, st, to: Ref):
        return st


def _mk(cap=8, **kw):
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=2,
                          inject_slots=8, spill_cap=64, **kw)
    rt = Runtime(opts).declare(Node, cap)
    return rt.start()


def test_released_unreachable_actor_is_collected():
    rt = _mk()
    ids = rt.spawn_many(Node, 4)
    rt.release(ids[2:])                 # host drops two refs
    assert rt.gc() == 2
    alive = np.asarray(rt.state.alive)
    assert alive.sum() == 2 and alive[ids[0]] and alive[ids[1]]


def test_state_field_ref_keeps_actor_alive():
    rt = _mk()
    a, b, c = rt.spawn_many(Node, 3)
    rt.send(int(a), Node.link, int(b))  # a.next_ref = b
    rt.run(max_steps=5)
    rt.release([b, c])
    assert rt.gc() == 1                 # only c: b is reachable from a
    alive = np.asarray(rt.state.alive)
    assert alive[a] and alive[b] and not alive[c]


def test_chain_reachability_is_transitive():
    rt = _mk(cap=8)
    ids = rt.spawn_many(Node, 6)
    for i in range(5):                  # 0 → 1 → 2 → 3 → 4 → 5
        rt.send(int(ids[i]), Node.link, int(ids[i + 1]))
    rt.run(max_steps=5)
    rt.release(ids[1:])
    assert rt.gc() == 0                 # whole chain hangs off ids[0]
    rt.release(ids[:1])
    assert rt.gc() == 6                 # now the entire chain goes


def test_cycle_of_garbage_is_collected():
    # ≙ the cycle detector's whole purpose (gc/cycle.c): rc alone never
    # frees a ring that references itself.
    rt = _mk()
    ids = rt.spawn_many(Node, 4)
    for i in range(4):
        rt.send(int(ids[i]), Node.link, int(ids[(i + 1) % 4]))
    rt.run(max_steps=5)
    rt.release(ids)
    assert rt.gc() == 4
    assert np.asarray(rt.state.alive).sum() == 0


def test_pending_message_is_a_root():
    rt = _mk()
    a, b = rt.spawn_many(Node, 2)
    rt.release([a, b])
    rt.send(int(a), Node.poke)          # queued via inject → host root now,
    assert rt.gc() == 1                 # only b collected
    rt.run(max_steps=5)                 # deliver + drain
    assert rt.state_of(int(a))["hits"] == 1
    assert rt.gc() == 1                 # quiet again → a goes too


def test_message_ref_arg_is_an_edge():
    rt = _mk()
    a, b = rt.spawn_many(Node, 2)
    # A message *in a's mailbox* carries b's ref; b has no other root.
    rt.bulk_send([int(a)], Node.link, [int(b)])
    rt.release([b])
    assert rt.gc() == 0                 # ref inside queued message
    rt.run(max_steps=5)                 # now a.next_ref = b (state edge)
    assert rt.gc() == 0
    rt.send(int(a), Node.link, -1)      # overwrite the field: b unreachable
    rt.run(max_steps=5)
    assert rt.gc() == 1


def test_auto_gc_in_run_loop():
    rt = _mk(cap=8, cd_interval=4)
    ids = rt.spawn_many(Node, 4)
    rt.release(ids[2:])
    # Keep the runtime busy past cd_interval steps: ping-pong traffic.
    for i in range(12):
        rt.send(int(ids[0]), Node.poke)
        rt.run(max_steps=2)
    assert rt.counter("n_collected") == 2
    # Collected slots are reclaimable by host spawn.
    rt.spawn(Node)
    assert np.asarray(rt.state.alive).sum() == 3


def test_gc_on_mesh_crosses_shards():
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=2,
                          inject_slots=8, spill_cap=64, mesh_shards=4)
    rt = Runtime(opts).declare(Node, 16).start()
    ids = rt.spawn_many(Node, 16)
    nl = rt.program.n_local
    # Cross-shard chain: each node links one on the *next* shard.
    order = sorted(range(16), key=lambda s: int(ids[s]) // nl)
    for i in range(15):
        rt.send(int(ids[order[i]]), Node.link, int(ids[order[i + 1]]))
    rt.run(max_steps=10)
    rt.release(ids)
    rt.pin([ids[order[0]]])
    assert rt.gc() == 0                 # chain root pinned: all reachable
    rt.release([ids[order[0]]])
    assert rt.gc() == 16
    assert np.asarray(rt.state.alive).sum() == 0


def test_heap_pressure_triggers_early_collection():
    """Host-heap allocation growth schedules a collection before
    cd_interval elapses (≙ the growth-triggered per-actor heap GC,
    mem/heap.c next_gc with --ponygcinitial/--ponygcfactor)."""
    from ponyc_tpu import Runtime, RuntimeOptions, actor, behaviour, I32

    @actor
    class Lonely:
        x: I32

        @behaviour
        def tick(self, st, v: I32):
            return {**st, "x": v}

    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=2,
                          inject_slots=8, cd_interval=10_000,
                          gc_initial=1 << 12)
    rt = Runtime(opts)
    rt.declare(Lonely, 4).start()
    a = rt.spawn(Lonely)
    garbage = rt.spawn(Lonely)
    rt.release(garbage)                 # unreachable → collectable
    assert rt.counter("n_collected") == 0
    # Allocate past gc_initial on the host heap, then run a few steps:
    # pressure must fire the collection long before cd_interval=10000.
    for _ in range(8):
        rt.heap.box(b"x" * 1024)
    for _ in range(3):
        rt.send(a, Lonely.tick, 1)
        rt.run(max_steps=4)
        if rt.counter("n_collected"):
            break
    assert rt.counter("n_collected") == 1
    assert rt.heap.bytes_since_gc == 0      # accounting reset
    assert rt.heap.stats()["bytes_live"] > 8 * 1024
