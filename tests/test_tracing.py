"""Causal message tracing tests (PROFILE.md §10): on-device trace
propagation through the mailbox ring side lanes, span reassembly into
causal trees, deterministic sampling, the zero-cost-when-off jaxpr
guarantee, the traced-vs-untraced differential, Perfetto flow-event
export, and the `trace` CLI — all tier-1 fast."""

import json
import os

import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,
                       analysis, behaviour)
from ponyc_tpu.models import ring
from ponyc_tpu.tracing import Tracer, consistent, load_spans, reassemble

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8, analysis=3,
                trace_sample=1)
    base.update(kw)
    return RuntimeOptions(**base)


# A 3-deep causal chain: inject -> Src.go -> Mid.relay -> Sink.take.

@actor
class Sink:
    n: I32

    @behaviour
    def take(self, st, v: I32):
        return {**st, "n": st["n"] + v}


@actor
class Mid:
    out: Ref[Sink]

    @behaviour
    def relay(self, st, v: I32):
        self.send(st["out"], Sink.take, v)
        return st


@actor
class Src:
    out: Ref[Mid]

    @behaviour
    def go(self, st, v: I32):
        self.send(st["out"], Mid.relay, v)
        return st


def _chain(opts):
    rt = Runtime(opts)
    rt.declare(Src, 2).declare(Mid, 2).declare(Sink, 2).start()
    sinks = rt.spawn_many(Sink, 2)
    mids = rt.spawn_many(Mid, 2, out=sinks)
    srcs = rt.spawn_many(Src, 2, out=mids)
    return rt, srcs, mids, sinks


# ------------------------------------------------------- propagation

@pytest.mark.parametrize("delivery", ["plan", "cosort"])
def test_propagation_three_deep_chain(delivery):
    """Acceptance: a sampled injection reassembles into a causal tree
    whose span ticks are consistent (enq <= disp <= retire, children
    nested under parents) across BOTH delivery formulations."""
    rt, srcs, _mids, _sinks = _chain(_opts(delivery=delivery))
    rt.send(int(srcs[0]), Src.go, 7)
    assert rt.run(max_steps=200) == 0
    trees = rt.traces()
    assert len(trees) == 1
    t = next(iter(trees.values()))
    assert t["n_spans"] == 4            # inject + 3 behaviour spans
    assert t["critical_path"] == ["inject", "Src.go", "Mid.relay",
                                  "Sink.take"]
    assert consistent(t)
    # every hop adds latency: the end-to-end number is positive
    assert t["latency"] >= 3
    # explicit nesting walk: each child's enqueue tick is the tick its
    # parent dispatched (the send happened inside that dispatch)
    root = t["roots"][0]
    s = root
    while s.children:
        (c,) = s.children
        assert s.enq <= s.disp <= s.retire
        assert c.enq >= s.disp
        s = c
    assert rt.state_of(int(_sinks[0]))["n"] == 7


def test_fanout_and_fused_dispatch_path():
    """One traced injection fanning out over MAX_SENDS=2 produces one
    tree with two branches; the fused Pallas dispatch path (interpret
    mode on CPU) propagates identically — trace lanes ride the outbox
    layout, not the dispatch implementation."""

    @actor
    class Fan:
        a: Ref[Sink]
        b: Ref[Sink]
        MAX_SENDS = 2

        @behaviour
        def go(self, st, v: I32):
            self.send(st["a"], Sink.take, v)
            self.send(st["b"], Sink.take, v)
            return st

    for fused in (False, True):
        rt = Runtime(_opts(max_sends=2, pallas_fused=fused))
        rt.declare(Fan, 1).declare(Sink, 2).start()
        sinks = rt.spawn_many(Sink, 2)
        fan = rt.spawn(Fan, a=int(sinks[0]), b=int(sinks[1]))
        rt.send(fan, Fan.go, 3)
        assert rt.run(max_steps=100) == 0
        t = next(iter(rt.traces().values()))
        assert t["n_spans"] == 4        # inject + Fan.go + 2×Sink.take
        assert consistent(t)
        fan_span = t["roots"][0].children[0]
        assert fan_span.beh == "Fan.go"
        assert sorted(c.beh for c in fan_span.children) \
            == ["Sink.take", "Sink.take"]


def test_host_behaviour_continues_trace():
    """A traced message delivered to a HOST cohort becomes a host span,
    and the host behaviour's sends continue the chain back onto the
    device — the trace crosses the device/host boundary both ways."""

    @actor
    class HostRelay:
        HOST = True
        out: Ref[Sink]

        @behaviour
        def relay(self, st, v: I32):
            self.send(st["out"], Sink.take, v)
            return st

    rt = Runtime(_opts(msg_words=2))
    rt.declare(HostRelay, 1).declare(Sink, 1).start()
    sink = rt.spawn(Sink)
    hr = rt.spawn(HostRelay, out=sink)
    # inject -> host relay -> device sink: the chain crosses the
    # boundary in both directions.
    rt.send(hr, HostRelay.relay, 5)
    assert rt.run(max_steps=200) == 0
    t = next(iter(rt.traces().values()))
    assert t["critical_path"] == ["inject", "HostRelay.relay",
                                  "Sink.take"]
    assert consistent(t)
    hspan = t["roots"][0].children[0]
    assert hspan.span_id % 2 == 1        # host spans are odd
    assert hspan.children[0].span_id % 2 == 0   # device spans even


# ---------------------------------------------------------- sampling

def test_sampling_deterministic_under_seed():
    a = Tracer(64, seed=7)
    b = Tracer(64, seed=7)
    sa = [a.sample() for _ in range(2048)]
    sb = [b.sample() for _ in range(2048)]
    assert sa == sb
    assert any(sa) and not all(sa)       # ~1-in-64, not degenerate
    c = Tracer(64, seed=8)
    assert [c.sample() for _ in range(2048)] != sa
    # rate sanity: 2048 draws at 1-in-64 ≈ 32 hits
    assert 8 <= sum(sa) <= 128


def test_sampling_deterministic_across_runs():
    """Two identical runs under a fixed seed trace the IDENTICAL set of
    injections — same trace count, same span structure."""
    def run_once():
        rt, ids = ring.build(8, _opts(trace_sample=4, trace_seed=3))
        for i in range(8):
            rt.send(int(ids[i]), ring.RingNode.token, 3)
        rt.run(max_steps=200)
        trees = rt.traces()
        return sorted((tid, t["n_spans"], t["latency"])
                      for tid, t in trees.items())

    first, second = run_once(), run_once()
    assert first == second
    assert 1 <= len(first) < 8           # sampled: some but not all


def test_explicit_trace_ids_and_bulk_send():
    """send(trace=N) / bulk_send(trace=N): the caller's id (the future
    ingress tier's request id) tags the device spans."""
    rt, srcs, _m, _s = _chain(_opts(trace_sample=1000000,
                                    inject_slots=16))
    rt.send(int(srcs[0]), Src.go, 1, trace=77)
    assert rt.run(max_steps=200) == 0
    rt.bulk_send(srcs, Src.go, [2, 2], trace=88)
    assert rt.run(max_steps=200) == 0
    trees = rt.traces()
    assert set(trees) == {77, 88}
    assert trees[77]["critical_path"][-1] == "Sink.take"
    # one root injection, both seeded messages branch under it
    assert trees[88]["n_spans"] == 1 + 2 * 3
    assert consistent(trees[77]) and consistent(trees[88])


# ------------------------------------------------- zero-cost when off

def test_state_carries_no_lanes_when_off():
    for opts in (_opts(trace_sample=0),
                 _opts(analysis=1, trace_sample=8)):
        rt, _ = ring.build(8, opts)
        assert rt.state.trace_buf == {}
        assert rt.state.span_data.size == 0
        assert rt._tracer is None
        with pytest.raises(RuntimeError, match="tracing"):
            rt.traces()


def test_jaxpr_identity_when_off(monkeypatch):
    """Acceptance: with tracing off (analysis<3 or trace_sample=0) the
    step jaxpr is bit-identical to a tracer-free build — proven PR-4
    style by (a) comparing jaxprs across inert knob settings and (b)
    trapping trace_span_lanes, the only source of the lanes."""
    import jax
    import jax.numpy as jnp

    from ponyc_tpu.program import Program
    from ponyc_tpu.runtime import engine
    from ponyc_tpu.runtime.state import init_state

    def build(analysis, sample):
        opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                              msg_words=1, spill_cap=16, inject_slots=4,
                              analysis=analysis, trace_sample=sample)
        prog = Program(opts)
        prog.declare(ring.RingNode, 8)
        prog.finalize()
        st = init_state(prog, opts)
        step = engine.build_step(prog, opts)
        k = opts.inject_slots
        inj_t = jnp.full((k,), -1, jnp.int32)
        inj_w = jnp.zeros((1 + opts.msg_words + opts.trace_lanes, k),
                          jnp.int32)
        return str(jax.make_jaxpr(step)(st, inj_t, inj_w))

    # trace_sample is inert below analysis 3: bit-identical jaxprs.
    assert build(2, 0) == build(2, 64)
    baseline3 = build(3, 0)

    def boom(*_a, **_k):
        raise AssertionError("trace lanes traced while tracing off")

    monkeypatch.setattr(engine, "trace_span_lanes", boom)
    assert build(3, 0) == baseline3     # trap unreached, identical
    assert build(2, 64) == build(2, 0)
    with pytest.raises(AssertionError, match="lanes traced"):
        build(3, 1)                     # and it IS the only source


# -------------------------------------------------------- differential

def test_differential_traced_vs_untraced():
    """Acceptance: sampling on changes NOTHING observable — delivery
    order (per-node pass counts), counters and CNF/ACK quiescence
    match an untraced run tick for tick."""
    def run_once(sample):
        rt, ids = ring.build(16, _opts(trace_sample=sample,
                                       inject_slots=16))
        for i in (0, 5, 11):
            rt.send(int(ids[i]), ring.RingNode.token, 20)
        code = rt.run(max_steps=500)
        passes = rt.cohort_state(ring.RingNode)["passes"].tolist()
        return (code, passes, rt.steps_run,
                rt.counter("n_processed"), rt.counter("n_delivered"))

    assert run_once(0) == run_once(1)


# --------------------------------------- span ring bounds / overflow

def test_span_ring_overflow_drops_and_counts():
    rt, ids = ring.build(8, _opts(trace_slots=4, quiesce_interval=64,
                                  pipeline=False))
    rt.send(int(ids[0]), ring.RingNode.token, 40)
    assert rt.run(max_steps=200) == 0
    trees = rt.traces()
    t = next(iter(trees.values()))
    assert rt._tracer.dropped > 0        # ring smaller than the trace
    assert consistent(t)                 # partial tree still consistent
    assert t["n_spans"] < 41


# ------------------------------------- Perfetto / spans.jsonl / CLI

def test_perfetto_flow_event_schema(tmp_path):
    """Acceptance: the Perfetto export carries span slices with flow
    arrows linking sender->receiver spans, plus process/thread name
    metadata for every track (the satellite)."""
    path = str(tmp_path / "an.csv")
    rt, srcs, _m, _s = _chain(_opts(analysis_path=path))
    rt.send(int(srcs[0]), Src.go, 2)
    rt.run(max_steps=200)
    rt.stop()
    spans_path = path + ".spans.jsonl"
    assert os.path.exists(spans_path)
    recs = load_spans(spans_path)
    assert len(recs) == 4
    for r in recs:
        assert set(r) == {"trace", "span", "parent", "beh", "actor",
                          "enq", "disp", "retire"}
    out = str(tmp_path / "t.json")
    analysis.chrome_trace(path, out)
    evs = json.load(open(out))["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    assert {s["name"] for s in slices} \
        == {"inject", "Src.go", "Mid.relay", "Sink.take"}
    for s in slices:
        assert isinstance(s["ts"], float) and s["dur"] >= 1
    starts = {e["id"]: e for e in evs if e["ph"] == "s"}
    ends = {e["id"]: e for e in evs if e["ph"] == "f"}
    assert len(starts) == 3 and set(starts) == set(ends)  # 3 arrows
    for fid, s in starts.items():
        assert ends[fid]["ts"] >= s["ts"]     # arrow points forward
    # track-name metadata: every tid that appears is labelled
    named = {(e["pid"], e.get("tid")) for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    used = {(e["pid"], e["tid"]) for e in evs
            if e["ph"] in ("X", "s", "f", "i")}
    assert used <= named | {(1, 0)}
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               and "traces" in e["args"]["name"] for e in evs)


def test_trace_cli(tmp_path, capsys):
    from ponyc_tpu.__main__ import main as cli_main
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(analysis_path=path))
    rt.send(int(ids[0]), ring.RingNode.token, 5)
    rt.run(max_steps=100)
    rt.stop()
    out = str(tmp_path / "cli.json")
    assert cli_main(["trace", path, "-o", out]) == 0
    doc = json.load(open(out))
    assert any(e["ph"] == "s" for e in doc["traceEvents"])
    capsys.readouterr()
    assert cli_main(["trace", "--tree", path + ".spans.jsonl"]) == 0
    tree_out = capsys.readouterr().out
    assert "critical path" in tree_out
    assert "RingNode.token" in tree_out
    # usage errors
    assert cli_main(["trace", "--tree"]) == 2
    assert cli_main(["trace", "--spans"]) == 2
    assert cli_main(["trace", path, "--spans",
                     str(tmp_path / "none.jsonl"), "-o", out]) == 2


def test_top_waiting_for_samples(tmp_path):
    """Satellite: empty, header-only and half-written CSVs render a
    waiting frame instead of crashing."""
    empty = str(tmp_path / "empty.csv")
    open(empty, "w").close()
    assert "waiting for samples" in analysis.top_frame(empty)
    header = str(tmp_path / "h.csv")
    with open(header, "w") as f:
        f.write(",".join(analysis.CSV_COLUMNS) + "\n")
    frame = analysis.top_frame(header)
    assert "waiting for samples" in frame and "no windows" in frame
    partial = str(tmp_path / "p.csv")
    with open(partial, "w") as f:
        f.write(",".join(analysis.CSV_COLUMNS) + "\n")
        f.write("not-a-number,oops")
    assert "waiting for samples" in analysis.top_frame(partial)


def test_top_trace_rows(tmp_path):
    path = str(tmp_path / "an.csv")
    rt, srcs, _m, _s = _chain(_opts(analysis_path=path))
    rt.send(int(srcs[0]), Src.go, 1)
    rt.run(max_steps=200)
    rt.stop()
    frame = analysis.top_frame(path)
    assert "traces: 1" in frame
    assert "Sink.take" in frame


# ------------------------------------------------------- validation

def test_option_validation():
    with pytest.raises(ValueError, match="trace_sample"):
        RuntimeOptions(trace_sample=-1)
    with pytest.raises(ValueError, match="trace_slots"):
        RuntimeOptions(trace_slots=0)
    assert RuntimeOptions(analysis=3, trace_sample=2).tracing
    assert not RuntimeOptions(analysis=2, trace_sample=2).tracing
    assert RuntimeOptions(analysis=3, trace_sample=0).trace_lanes == 0
    assert RuntimeOptions(analysis=3, trace_sample=1).trace_lanes == 2
