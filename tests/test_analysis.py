"""Analysis/telemetry tests (≙ --ponyanalysis levels, analysis.c; the CSV
stream + SIGTERM dump are the fork's observability features)."""

import os
import signal

import numpy as np

from ponyc_tpu import Runtime, RuntimeOptions, analysis
from ponyc_tpu.models import ring


def _build(n, **kw):
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                          spill_cap=64, inject_slots=8, **kw)
    rt = Runtime(opts).declare(ring.RingNode, n).start()
    ids = rt.spawn_many(ring.RingNode, n)
    rt.set_fields(ring.RingNode, ids, next_ref=np.roll(ids, -1))
    return rt, ids


def test_level2_csv_stream(tmp_path):
    path = str(tmp_path / "an.csv")
    rt, ids = _build(8, analysis=2, analysis_path=path)
    rt.send(int(ids[0]), ring.RingNode.token, 100)
    rt.run()
    rt.stop()
    lines = open(path).read().strip().split("\n")
    header = lines[0].split(",")
    assert header == analysis.CSV_COLUMNS
    rows = [dict(zip(header, l.split(","))) for l in lines[1:]]
    assert rows, "no telemetry rows written"
    assert sum(int(r["processed"]) for r in rows) == 100
    # seed + 99 forwards (the hop-0 send is masked by when=hops>0)
    assert sum(int(r["delivered"]) for r in rows) == 100
    # occupancy aggregates are real reductions at level >= 1
    assert any(int(r["occ_sum"]) > 0 or int(r["processed"]) > 0
               for r in rows)


def test_level0_costs_nothing_and_writes_nothing(tmp_path):
    path = str(tmp_path / "an.csv")
    rt, ids = _build(8, analysis=0, analysis_path=path)
    rt.send(int(ids[0]), ring.RingNode.token, 10)
    rt.run()
    rt.stop()
    assert not os.path.exists(path)
    assert getattr(rt, "_analysis", None) is None


def test_dump_reports_live_world():
    rt, ids = _build(8, analysis=1)
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    rt.run()
    a = analysis.attach(rt)
    text = a.dump(out=open(os.devnull, "w"))
    assert "actors_alive=8" in text
    assert "cohort RingNode" in text
    assert "n_processed=50" in text
    a.close()


def test_signal_dump_handler(tmp_path, capfd):
    rt, ids = _build(4, analysis=1)
    rt.send(int(ids[0]), ring.RingNode.token, 5)
    rt.run()
    a = analysis.attach(rt)        # installs SIGTERM/SIGUSR1 handlers
    os.kill(os.getpid(), signal.SIGUSR1)
    err = capfd.readouterr().err
    assert "ponyc_tpu analysis dump" in err
    a.close()
    signal.signal(signal.SIGUSR1, signal.SIG_DFL)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
