"""Analysis/telemetry tests (≙ --ponyanalysis levels, analysis.c; the CSV
stream + SIGTERM dump are the fork's observability features)."""

import os
import signal

import numpy as np

from ponyc_tpu import Runtime, RuntimeOptions, analysis
from ponyc_tpu.models import ring


def _build(n, **kw):
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                          spill_cap=64, inject_slots=8, **kw)
    rt = Runtime(opts).declare(ring.RingNode, n).start()
    ids = rt.spawn_many(ring.RingNode, n)
    rt.set_fields(ring.RingNode, ids, next_ref=np.roll(ids, -1))
    return rt, ids


def test_level2_csv_stream(tmp_path):
    path = str(tmp_path / "an.csv")
    rt, ids = _build(8, analysis=2, analysis_path=path)
    rt.send(int(ids[0]), ring.RingNode.token, 100)
    rt.run()
    rt.stop()
    lines = open(path).read().strip().split("\n")
    header = lines[0].split(",")
    # Static columns lead; the profiler appends per-behaviour `run:`
    # deltas and per-cohort queue-wait percentiles after them.
    assert header[:len(analysis.CSV_COLUMNS)] == analysis.CSV_COLUMNS
    assert "run:RingNode.token" in header
    assert "qw50:RingNode" in header and "qw99:RingNode" in header
    rows = [dict(zip(header, l.split(","))) for l in lines[1:]]
    assert rows, "no telemetry rows written"
    assert sum(int(r["processed"]) for r in rows) == 100
    # seed + 99 forwards (the hop-0 send is masked by when=hops>0)
    assert sum(int(r["delivered"]) for r in rows) == 100
    # per-behaviour attribution sums to the mesh-wide total
    assert sum(int(r["run:RingNode.token"]) for r in rows) == 100
    # occupancy aggregates are real reductions at level >= 1
    assert any(int(r["occ_sum"]) > 0 or int(r["processed"]) > 0
               for r in rows)


def test_level0_costs_nothing_and_writes_nothing(tmp_path):
    path = str(tmp_path / "an.csv")
    rt, ids = _build(8, analysis=0, analysis_path=path)
    rt.send(int(ids[0]), ring.RingNode.token, 10)
    rt.run()
    rt.stop()
    assert not os.path.exists(path)
    assert getattr(rt, "_analysis", None) is None


def test_dump_reports_live_world():
    rt, ids = _build(8, analysis=1)
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    rt.run()
    a = analysis.attach(rt)
    text = a.dump(out=open(os.devnull, "w"))
    assert "actors_alive=8" in text
    assert "cohort RingNode" in text
    assert "n_processed=50" in text
    a.close()


def test_signal_dump_handler(tmp_path, capfd):
    rt, ids = _build(4, analysis=1)
    rt.send(int(ids[0]), ring.RingNode.token, 5)
    rt.run()
    a = analysis.attach(rt)        # installs SIGTERM/SIGUSR1 handlers
    os.kill(os.getpid(), signal.SIGUSR1)
    err = capfd.readouterr().err
    assert "ponyc_tpu analysis dump" in err
    a.close()
    signal.signal(signal.SIGUSR1, signal.SIG_DFL)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)


def test_level3_per_event_rows(tmp_path):
    """Level 3 = per-event lane (≙ the fork's per-event analysis rows,
    analysis.c:587-692): a program that spawns, destroys, mutes and
    errors must leave one row per transition in the events CSV."""
    from ponyc_tpu import I32, Ref, actor, behaviour

    @actor
    class Child:
        x: I32

        @behaviour
        def init(self, st, v: I32):
            self.error_int(7, when=v == 1)
            self.destroy(when=v == 1)
            return {**st, "x": v}

    @actor
    class Boss:
        SPAWNS = {"Child": 1}
        made: I32

        @behaviour
        def make(self, st, v: I32):
            self.spawn(Child.init, v)
            return {**st, "made": st["made"] + 1}

    @actor
    class Slow:
        total: I32
        BATCH = 1

        @behaviour
        def eat(self, st, v: I32):
            return {**st, "total": st["total"] + v}

    @actor
    class Flood:
        out: Ref[Slow]
        left: I32
        MAX_SENDS = 2

        @behaviour
        def go(self, st, _: I32):
            self.send(st["out"], Slow.eat, 1, when=st["left"] > 0)
            self.send(self.actor_id, Flood.go, 0, when=st["left"] > 1)
            return {**st, "left": st["left"] - 1}

    path = str(tmp_path / "an3.csv")
    opts = RuntimeOptions(mailbox_cap=4, batch=2, max_sends=2, msg_words=2,
                          spill_cap=256, inject_slots=64, analysis=3,
                          analysis_path=path)
    rt = Runtime(opts)
    rt.declare(Boss, 1).declare(Child, 4).declare(Slow, 1) \
      .declare(Flood, 8).start()
    boss = rt.spawn(Boss)
    sink = rt.spawn(Slow)
    floods = rt.spawn_many(Flood, 8, out=int(sink), left=6)
    rt.send(boss, Boss.make, 1)      # spawn + error + destroy
    for f in floods:
        rt.send(int(f), Flood.go, 0)  # overload + mute + unmute
    rt.run(max_steps=400)
    rt.stop()
    ev_path = path + ".events.csv"
    assert os.path.exists(ev_path)
    lines = open(ev_path).read().strip().split("\n")
    assert lines[0].split(",") == analysis.EVENT_COLUMNS
    events = [l.split(",")[2] for l in lines[1:]]
    for want in ("SPAWN", "DESTROY", "ERROR", "MUTE", "UNMUTE",
                 "OVERLOAD"):
        assert want in events, (want, sorted(set(events)))
    assert rt.state_of(int(sink))["total"] == 8 * 6


def test_level3_ring_overflow_counts_drops(tmp_path):
    """A deliberately tiny event ring under mute churn records what fits
    and COUNTS the rest (ev_dropped) instead of silently truncating
    (≙ the fork's analysis queue never silently losing events)."""
    import numpy as np

    from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, \
        behaviour

    @actor
    class SlowE:
        n: I32
        BATCH = 1

        @behaviour
        def eat(self, st, v: I32):
            return {**st, "n": st["n"] + 1}

    @actor
    class FastE:
        out: Ref[SlowE]
        left: I32
        MAX_SENDS = 2

        @behaviour
        def go(self, st, _: I32):
            self.send(st["out"], SlowE.eat, 1, when=st["left"] > 0)
            self.send(self.actor_id, FastE.go, 0, when=st["left"] > 1)
            return {**st, "left": st["left"] - 1}

    path = str(tmp_path / "ev.csv")
    rt = Runtime(RuntimeOptions(mailbox_cap=2, batch=1, msg_words=1,
                                max_sends=2, spill_cap=512,
                                inject_slots=16, analysis=3,
                                analysis_events=8, analysis_path=path))
    rt.declare(FastE, 12).declare(SlowE, 1).start()
    s = rt.spawn(SlowE)
    fs = rt.spawn_many(FastE, 12, out=s, left=30)
    rt.bulk_send(fs, FastE.go, np.zeros(12, np.int64))
    assert rt.run(max_steps=30_000) == 0
    rt.stop()
    import os
    rows = open(path + ".events.csv").read().strip().splitlines()
    assert len(rows) > 1, "events must be recorded"
    assert int(rt.state.ev_dropped[0]) > 0, "tiny ring must count drops"


def test_chrome_trace_export(tmp_path):
    """chrome_trace (≙ the dtrace/systemtap timeline scripts,
    examples/dtrace/telemetry.d): CSVs → Chrome-trace JSON with counter
    tracks per window and instant events per level-3 transition."""
    import json

    from ponyc_tpu import I32, Ref, actor, behaviour

    @actor
    class TBoss:
        SPAWNS = {"TKid": 1}
        made: I32

        @behaviour
        def make(self, st, v: I32):
            self.spawn(TKid.init, v)
            return {**st, "made": st["made"] + 1}

    @actor
    class TKid:
        x: I32

        @behaviour
        def init(self, st, v: I32):
            self.destroy(when=v == 1)
            return {**st, "x": v}

    path = str(tmp_path / "an.csv")
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                          msg_words=1, spill_cap=64, inject_slots=8,
                          analysis=3, analysis_path=path)
    rt = Runtime(opts).declare(TBoss, 1).declare(TKid, 8).start()
    boss = rt.spawn(TBoss)
    for v in (0, 1, 2):
        rt.send(boss, TBoss.make, v)
    rt.run()
    rt.stop()
    out = str(tmp_path / "trace.json")
    analysis.chrome_trace(path, out)
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    counters = [e for e in evs if e["ph"] == "C"]
    assert counters, "no counter tracks"
    names = {e["name"] for e in counters}
    assert {"queue", "actors", "window throughput"} <= names
    total_processed = sum(e["args"].get("processed", 0) for e in counters
                          if e["name"] == "window throughput")
    assert total_processed == 6          # 3 makes + 3 ctor inits
    # Device-side SPAWN/DESTROY transitions land as instant events.
    instants = [e for e in evs if e["ph"] == "i"]
    assert any(e["name"].startswith("SPAWN") for e in instants)
    assert any(e["name"].startswith("DESTROY") for e in instants)
    # CLI path: same conversion through `python -m ponyc_tpu trace`.
    from ponyc_tpu.__main__ import main as cli_main
    out2 = str(tmp_path / "t2.json")
    assert cli_main(["trace", path, "-o", out2]) == 0
    assert json.load(open(out2))["traceEvents"]


def test_host_rss_cpu_accounting(tmp_path):
    """Host-loop CPU/RSS accounting (≙ ponyint_update_memory_usage,
    sched/cpu.c): every window row carries the process's current RSS
    and cumulative CPU time; the dump prints them too."""
    path = str(tmp_path / "an.csv")
    rt, ids = _build(8, analysis=2, analysis_path=path)
    rt.send(int(ids[0]), ring.RingNode.token, 40)
    rt.run()
    text = rt._analysis.dump(out=open(os.devnull, "w"))
    assert "host_rss_kb=" in text and "host_cpu_ms=" in text
    rt.stop()
    lines = open(path).read().strip().split("\n")
    header = lines[0].split(",")
    assert "rss_kb" in header and "cpu_ms" in header
    rows = [dict(zip(header, l.split(","))) for l in lines[1:]]
    assert all(int(r["rss_kb"]) > 1000 for r in rows)      # > 1 MB RSS
    assert all(float(r["cpu_ms"]) > 0 for r in rows)


def test_chrome_trace_tolerates_truncated_final_row(tmp_path, capfd):
    """Satellite (PR 7): a run killed mid-flush leaves a truncated
    final CSV row (and event row) — chrome_trace parses what is whole
    and warns once instead of raising (the postmortem workflow reads
    exactly these files after a crash)."""
    import json
    path = str(tmp_path / "an.csv")
    # A small fixed window → several CSV rows, so truncating the last
    # still leaves whole ones to convert.
    rt, ids = _build(8, analysis=3, analysis_path=path,
                     quiesce_interval=8)
    rt.send(int(ids[0]), ring.RingNode.token, 40)
    rt.run()
    rt.stop()
    # A quiet ring emits no transition events: seed the events CSV with
    # one whole and one to-be-truncated row so both readers are hit.
    with open(path + ".events.csv", "a") as f:
        f.write("5.0,3,MUTE,4\n9.0,7,UNMUTE,4\n")
    # Truncate the last line of both CSVs mid-row (killed mid-flush).
    for p in (path, path + ".events.csv"):
        raw = open(p).read().rstrip("\n")
        assert "\n" in raw, p
        open(p, "w").write(raw[: raw.rfind("\n") + 4])
    out = str(tmp_path / "t.json")
    analysis._warned_truncated.clear()
    analysis.chrome_trace(path, out)
    doc = json.load(open(out))
    assert any(e["name"] == "window throughput"
               for e in doc["traceEvents"])
    err = capfd.readouterr().err
    assert err.count("incomplete row") >= 1
    # warn ONCE per file per process: a second read stays quiet
    analysis.chrome_trace(path, out)
    assert "incomplete row" not in capfd.readouterr().err
    # top_frame reads the same truncated file calmly
    assert "step " in analysis.top_frame(path)


def test_chrome_trace_header_only_csv(tmp_path):
    """A run killed during warmup leaves a header-only CSV: convert to
    an (empty but valid) trace instead of raising."""
    import json
    path = str(tmp_path / "empty.csv")
    open(path, "w").write(",".join(analysis.CSV_COLUMNS) + "\n")
    out = str(tmp_path / "t.json")
    analysis.chrome_trace(path, out)
    doc = json.load(open(out))
    assert all(e["ph"] == "M" for e in doc["traceEvents"])
