"""Sendability checker (capability-lite type system).

≙ the reference compiler's type-system guarantees re-expressed at this
framework's static boundary (the build/trace): typed actor references
(`Ref[T]`) verify wiring at send/spawn/set_fields, miswired programs
fail at build rather than badmsg-ing at runtime (≙ type/safeto.c,
type/cap.c sendability; expr/call.c method-on-type checks), and
HostHeap handles are move-only, the dynamic analog of an `iso` send
(≙ gc/serialise ownership transfer; use-after-send is rejected).
"""

import numpy as np
import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.hostmem import HostHeap

OPTS = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=2,
                      inject_slots=8)


@actor
class Sink:
    total: I32

    @behaviour
    def add(self, st, v: I32):
        return {**st, "total": st["total"] + v}


@actor
class Other:
    x: I32

    @behaviour
    def poke(self, st, v: I32):
        return {**st, "x": v}


def test_typed_field_wrong_behaviour_fails_at_build():
    @actor
    class Src:
        out: Ref[Sink]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            # Wrong: `out` is Ref[Sink] but this sends Other.poke.
            self.send(st["out"], Other.poke, v)
            return st

    rt = Runtime(OPTS)
    rt.declare(Src, 1).declare(Sink, 1).declare(Other, 1).start()
    s = rt.spawn(Src)
    rt.send(s, Src.go, 1)
    with pytest.raises(TypeError, match="sendability"):
        rt.run(max_steps=4)      # trace time = first run


def test_typed_field_correct_wiring_runs():
    @actor
    class Src:
        out: Ref[Sink]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Sink.add, v)
            return st

    rt = Runtime(OPTS)
    rt.declare(Src, 2).declare(Sink, 2).declare(Other, 1).start()
    k = rt.spawn(Sink)
    s = rt.spawn(Src, out=int(k))
    rt.send(s, Src.go, 7)
    assert rt.run(max_steps=8) == 0
    assert rt.state_of(int(k))["total"] == 7


def test_typed_store_mismatch_fails_at_build():
    @actor
    class Src:
        out: Ref[Sink]
        pal: Ref[Other]

        @behaviour
        def rewire(self, st, v: I32):
            # Wrong: stores the Ref[Other] field into the Ref[Sink] slot.
            return {**st, "out": st["pal"]}

    rt = Runtime(OPTS)
    rt.declare(Src, 1).declare(Sink, 1).declare(Other, 1).start()
    s = rt.spawn(Src)
    rt.send(s, Src.rewire, 0)
    with pytest.raises(TypeError, match="sendability"):
        rt.run(max_steps=4)


def test_typed_arg_rides_through_send():
    @actor
    class Fwd:
        MAX_SENDS = 1

        @behaviour
        def fwd(self, st, tgt: Ref[Sink], v: I32):
            # tgt arrives typed; sending the wrong behaviour must fail.
            self.send(tgt, Other.poke, v)
            return st

    rt = Runtime(OPTS)
    rt.declare(Fwd, 1).declare(Sink, 1).declare(Other, 1).start()
    f = rt.spawn(Fwd)
    k = rt.spawn(Sink)
    rt.send(f, Fwd.fwd, int(k), 3)
    with pytest.raises(TypeError, match="sendability"):
        rt.run(max_steps=4)


def test_host_send_wrong_cohort_raises():
    rt = Runtime(OPTS)
    rt.declare(Sink, 2).declare(Other, 2).start()
    o = rt.spawn(Other)
    with pytest.raises(TypeError, match="sendability"):
        rt.send(int(o), Sink.add, 1)


def test_host_send_ref_arg_wrong_cohort_raises():
    @actor
    class Fwd:
        MAX_SENDS = 1

        @behaviour
        def fwd(self, st, tgt: Ref[Sink], v: I32):
            self.send(tgt, Sink.add, v)
            return st

    rt = Runtime(OPTS)
    rt.declare(Fwd, 1).declare(Sink, 1).declare(Other, 1).start()
    f = rt.spawn(Fwd)
    o = rt.spawn(Other)
    with pytest.raises(TypeError, match="sendability"):
        rt.send(int(f), Fwd.fwd, int(o), 1)    # o is not a Sink


def test_spawn_field_wrong_cohort_raises():
    @actor
    class Src:
        out: Ref[Sink]

        @behaviour
        def go(self, st, v: I32):
            return st

    rt = Runtime(OPTS)
    rt.declare(Src, 2).declare(Sink, 1).declare(Other, 1).start()
    o = rt.spawn(Other)
    with pytest.raises(TypeError, match="sendability"):
        rt.spawn(Src, out=int(o))


def test_set_fields_wrong_cohort_raises():
    @actor
    class Src:
        out: Ref[Sink]

        @behaviour
        def go(self, st, v: I32):
            return st

    rt = Runtime(OPTS)
    rt.declare(Src, 2).declare(Sink, 1).declare(Other, 1).start()
    s = rt.spawn(Src)
    o = rt.spawn(Other)
    with pytest.raises(TypeError, match="sendability"):
        rt.set_fields(Src, [s], out=np.asarray([int(o)]))


def test_undeclared_ref_target_fails_at_finalize():
    @actor
    class Lost:
        out: Ref["NeverDeclared"]

        @behaviour
        def go(self, st, v: I32):
            return st

    rt = Runtime(OPTS)
    rt.declare(Lost, 1)
    with pytest.raises(TypeError, match="not declared"):
        rt.start()


def test_spawned_ref_is_typed():
    @actor
    class Child:
        x: I32

        @behaviour
        def init(self, st, v: I32):
            return {**st, "x": v}

    @actor
    class Parent:
        kid: Ref[Child]
        MAX_SENDS = 2
        SPAWNS = {"Child": 1}

        @behaviour
        def make(self, st, v: I32):
            ref = self.spawn(Child.init, v)
            # Wrong: the spawned ref is typed Ref[Child].
            self.send(ref, Other.poke, v)
            return {**st, "kid": ref}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=2,
                                msg_words=2, inject_slots=8))
    rt.declare(Parent, 1).declare(Child, 2).declare(Other, 1).start()
    p = rt.spawn(Parent)
    rt.send(p, Parent.make, 5)
    with pytest.raises(TypeError, match="sendability"):
        rt.run(max_steps=4)


def test_untyped_ref_stays_permissive():
    @actor
    class Loose:
        out: Ref                     # untyped: no wiring check
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Sink.add, v)
            return st

    rt = Runtime(OPTS)
    rt.declare(Loose, 1).declare(Sink, 1).declare(Other, 1).start()
    k = rt.spawn(Sink)
    lo = rt.spawn(Loose, out=int(k))
    rt.send(lo, Loose.go, 2)
    assert rt.run(max_steps=8) == 0
    assert rt.state_of(int(k))["total"] == 2


def test_typed_refs_work_in_jnp_ops():
    # Typed refs are PLAIN arrays (provenance rides on trace identity),
    # so the standard masked-ref idiom must keep working; the derived
    # value is untyped (gradual), never a crash.
    import jax.numpy as jnp

    @actor
    class Src:
        out: Ref[Sink]
        alt: Ref[Sink]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            tgt = jnp.where(v > 0, st["out"], st["alt"])   # derived ref
            self.send(tgt, Sink.add, v)
            return {**st, "out": jnp.where(v > 2, st["alt"], st["out"])}

    rt = Runtime(OPTS)
    rt.declare(Src, 1).declare(Sink, 2).declare(Other, 1).start()
    k1, k2 = rt.spawn(Sink), rt.spawn(Sink)
    s = rt.spawn(Src, out=int(k1), alt=int(k2))
    rt.send(s, Src.go, 9)
    assert rt.run(max_steps=8) == 0
    assert rt.state_of(int(k1))["total"] == 9


def test_typed_arg_mismatch_in_device_send():
    @actor
    class Registry:
        MAX_SENDS = 0

        @behaviour
        def register(self, st, who: Ref[Sink]):
            return st

    @actor
    class Src:
        reg: Ref[Registry]
        pal: Ref[Other]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            # Wrong: passes a Ref[Other] where register wants Ref[Sink].
            self.send(st["reg"], Registry.register, st["pal"])
            return st

    rt = Runtime(OPTS)
    rt.declare(Registry, 1).declare(Src, 1).declare(Sink, 1) \
      .declare(Other, 1).start()
    s = rt.spawn(Src)
    rt.send(s, Src.go, 1)
    with pytest.raises(TypeError, match="sendability"):
        rt.run(max_steps=4)


def test_hostheap_handles_are_move_only():
    h = HostHeap()
    hd = h.box({"payload": 1})
    assert h.peek(hd) == {"payload": 1}      # peek does not consume
    assert h.unbox(hd) == {"payload": 1}
    with pytest.raises(KeyError):
        h.unbox(hd)                           # double-take = use-after-send
    assert h.live == 0
