"""The FULL six-cap lattice: iso/trn/ref(Mut)/val/box/tag with viewpoint
adaptation — matrix tests naming every cap pair.

≙ src/libponyc/type/cap.c:59-160 (is_cap_sub_cap), cap.c:581-711
(cap_view_upper), type/alias.c (cap_aliasing: iso→tag, trn→box) and
safeto.c's CAP_SEND {iso, val, tag}. The store matrix, the viewpoint
table and the alias rule below are transcribed row-by-row from those
functions; any edit here must cite a corresponding reference change.
"""

import pytest

from ponyc_tpu import (Box, I32, Iso, Mut, Ref, Runtime, RuntimeOptions,
                       Tag, Trn, Val, actor, behaviour)
from ponyc_tpu.hostmem import CapabilityError, HandleRef, HostHeap
from ponyc_tpu.ops import pack

CAPS = ("iso", "trn", "ref", "val", "box", "tag")

OPTS = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=2,
                      inject_slots=8)


# ---------------- the store lattice, every pair ----------------

# (src stored into dst) — True rows follow is_cap_sub_cap with unique
# sources consumed (iso^/trn^): cap.c:80-97 (iso sub of all),
# 99-113 (trn sub of all but iso), 115-124 (ref <: ref, box),
# 126-136 (val <: val, box), 138-146 (box <: box), super tag always
# true (cap.c:73-74).
EXPECTED_STORE = {
    ("iso", "iso"): True, ("iso", "trn"): True, ("iso", "ref"): True,
    ("iso", "val"): True, ("iso", "box"): True, ("iso", "tag"): True,
    ("trn", "iso"): False, ("trn", "trn"): True, ("trn", "ref"): True,
    ("trn", "val"): True, ("trn", "box"): True, ("trn", "tag"): True,
    ("ref", "iso"): False, ("ref", "trn"): False, ("ref", "ref"): True,
    ("ref", "val"): False, ("ref", "box"): True, ("ref", "tag"): True,
    ("val", "iso"): False, ("val", "trn"): False, ("val", "ref"): False,
    ("val", "val"): True, ("val", "box"): True, ("val", "tag"): True,
    ("box", "iso"): False, ("box", "trn"): False, ("box", "ref"): False,
    ("box", "val"): False, ("box", "box"): True, ("box", "tag"): True,
    ("tag", "iso"): False, ("tag", "trn"): False, ("tag", "ref"): False,
    ("tag", "val"): False, ("tag", "box"): False, ("tag", "tag"): True,
}


@pytest.mark.parametrize("src", CAPS)
@pytest.mark.parametrize("dst", CAPS)
def test_store_lattice_pair(src, dst):
    assert pack.cap_store_ok(src, dst) is EXPECTED_STORE[(src, dst)], \
        f"{src} stored into {dst}"


def test_store_lattice_gradual():
    for m in CAPS:
        assert pack.cap_store_ok(None, m)
        assert pack.cap_store_ok(m, None)


# ---------------- viewpoint adaptation, every pair ----------------

# origin▷field — transcribed from cap_view_upper (cap.c:581-711):
# tag origin sees nothing (588-596); field tag is always tag (600-602);
# iso▷: iso→iso, val→val, else tag (604-624); trn▷: iso→iso, trn→trn,
# val→val, else box (626-651); ref▷T = T (653-654); val▷T = val
# (656-672); box▷: iso→tag, val→val, else box (674-699).
EXPECTED_VIEW = {
    "iso": {"iso": "iso", "trn": "tag", "ref": "tag", "val": "val",
            "box": "tag", "tag": "tag"},
    "trn": {"iso": "iso", "trn": "trn", "ref": "box", "val": "val",
            "box": "box", "tag": "tag"},
    "ref": {"iso": "iso", "trn": "trn", "ref": "ref", "val": "val",
            "box": "box", "tag": "tag"},
    "val": {"iso": "val", "trn": "val", "ref": "val", "val": "val",
            "box": "val", "tag": "tag"},
    "box": {"iso": "tag", "trn": "box", "ref": "box", "val": "val",
            "box": "box", "tag": "tag"},
    "tag": {c: None for c in CAPS},
}


@pytest.mark.parametrize("origin", CAPS)
@pytest.mark.parametrize("field", CAPS)
def test_viewpoint_pair(origin, field):
    assert pack.viewpoint(origin, field) == EXPECTED_VIEW[origin][field], \
        f"{origin}▷{field}"


def test_alias_rule():
    # cap_aliasing (alias.c): iso aliases as tag, trn as box, rest self.
    assert pack.cap_alias("iso") == "tag"
    assert pack.cap_alias("trn") == "box"
    for m in ("ref", "val", "box", "tag"):
        assert pack.cap_alias(m) == m


def test_sendable_set_is_cap_send():
    # TK_CAP_SEND {iso, val, tag} (cap.c:90).
    assert {m for m in CAPS if pack.cap_sendable(m)} == \
        {"iso", "val", "tag"}


# ---------------- sendability at the behaviour boundary ----------------

@pytest.mark.parametrize("capspec", [Trn, Mut, Box])
def test_local_caps_are_not_sendable_parameters(capspec):
    with pytest.raises(TypeError, match="not sendable"):
        @actor
        class Bad:
            x: I32

            @behaviour
            def take(self, st, h: capspec):
                return st


def test_local_caps_are_legal_fields():
    @actor
    class LocalState:
        scratch: Trn
        view: Box
        cell: Mut
        n: I32

        @behaviour
        def tick(self, st):
            return {**st, "n": st["n"] + 1}

    rt = Runtime(OPTS)
    rt.declare(LocalState, 1).start()
    a = rt.spawn(LocalState)
    rt.send(a, LocalState.tick)
    rt.run(max_steps=4)
    assert int(rt.cohort_state(LocalState)["n"][0]) == 1


# ---------------- trace-time trn discipline ----------------

def _run_one(cls, beh, *args):
    rt = Runtime(OPTS)
    rt.declare(cls, 1).start()
    a = rt.spawn(cls)
    rt.send(a, beh, *args)
    rt.run(max_steps=4)
    return rt


def test_trn_keep_in_place_is_legal():
    @actor
    class Keep:
        t: Trn

        @behaviour
        def hold(self, st):
            return st                      # keeping the trn field: free

    _run_one(Keep, Keep.hold)


def test_trn_keep_plus_box_alias_is_legal():
    # Pony's trn+box sharing: one writer, read views alias freely.
    @actor
    class Share:
        t: Trn
        v: Box

        @behaviour
        def share(self, st):
            return {**st, "v": st["t"]}

    _run_one(Share, Share.share)


def test_trn_consumed_into_second_trn_field_requires_clearing():
    @actor
    class MoveKeep:
        t: Trn
        u: Trn

        @behaviour
        def leak(self, st):
            # moves t into u but ALSO keeps t — use-after-consume.
            return {**st, "u": st["t"]}

    with pytest.raises(TypeError, match="retains it|use-after-consume"):
        _run_one(MoveKeep, MoveKeep.leak)


def test_trn_move_with_clear_is_legal():
    @actor
    class MoveClear:
        t: Trn
        u: Trn

        @behaviour
        def move(self, st):
            return {**st, "u": st["t"], "t": -1}

    _run_one(MoveClear, MoveClear.move)


def test_trn_double_consume_rejected():
    @actor
    class DoubleMove:
        t: Trn
        u: Trn
        w: Mut

        @behaviour
        def boom(self, st):
            return {**st, "u": st["t"], "w": st["t"], "t": -1}

    with pytest.raises(TypeError, match="write-unique|BOTH fields"):
        _run_one(DoubleMove, DoubleMove.boom)


def test_val_cannot_enter_trn_field():
    @actor
    class Freeze:
        t: Trn

        @behaviour
        def put(self, st, h: Val):
            return {**st, "t": h}

    with pytest.raises(TypeError, match="cannot grant"):
        _run_one(Freeze, Freeze.put, 7)


def test_iso_arg_may_land_in_any_writable_field():
    @actor
    class Sink:
        t: Trn
        m: Mut

        @behaviour
        def take_t(self, st, h: Iso):
            return {**st, "t": h}

    _run_one(Sink, Sink.take_t, 7)


# ---------------- HostHeap dynamic rules ----------------

def test_heap_write_rights_matrix():
    hh = HostHeap()
    for m in CAPS:
        h = hh.box(["x"], mode=m)
        if m in ("iso", "trn", "ref"):
            hh.poke(h, ["y"])
            assert hh.peek(h) == ["y"]
        else:
            with pytest.raises(CapabilityError):
                hh.poke(h, ["y"])


def test_heap_read_rights():
    hh = HostHeap()
    for m in CAPS:
        h = hh.box("obj", mode=m)
        if m == "tag":
            with pytest.raises(CapabilityError):
                hh.peek(h)
        else:
            assert hh.peek(h) == "obj"


def test_heap_unbox_rights():
    hh = HostHeap()
    for m in CAPS:
        h = hh.box("obj", mode=m)
        if m in ("iso", "trn"):
            assert hh.unbox(h) == "obj"
        else:
            with pytest.raises(CapabilityError):
                hh.unbox(h)


def test_heap_view_legality_follows_alias_rule():
    hh = HostHeap()
    for src in CAPS:
        aliased = pack.cap_alias(src)
        for dst in CAPS:
            h = hh.box("obj", mode=src)
            if pack.cap_store_ok(aliased, dst):
                v = hh.view(h, dst)
                assert hh.mode(v) == dst
            else:
                with pytest.raises(CapabilityError):
                    hh.view(h, dst)


def test_heap_box_view_of_trn_reads_while_owner_writes():
    hh = HostHeap()
    t = hh.box({"n": 1}, mode="trn")
    v = hh.view(t, "box")
    assert hh.peek(v) == {"n": 1}
    hh.poke(t, {"n": 2})
    assert hh.peek(v) == {"n": 2}          # view tracks the one writer
    with pytest.raises(CapabilityError):
        hh.poke(v, {})                     # box never writes


def test_heap_viewpoint_field_read_composition():
    hh = HostHeap()
    inner_iso = hh.box("secret", mode="iso")
    inner_ref = hh.box(["mutable"], mode="ref")
    outer = hh.box({"i": HandleRef(inner_iso), "r": HandleRef(inner_ref),
                    "plain": 42}, mode="trn")
    # trn▷ref = box: readable view, no write rights.
    vr = hh.peek_field(outer, "r")
    assert hh.mode(vr) == "box" and hh.peek(vr) == ["mutable"]
    # trn▷iso = iso, but a field READ binds alias(iso) = tag — reading
    # can never mint a second owner of a unique (alias.c).
    vi0 = hh.peek_field(outer, "i")
    assert hh.mode(vi0) == "tag"
    # box origin: box▷iso = tag — identity only.
    bouter = hh.view(outer, "box")
    vi = hh.peek_field(bouter, "i")
    assert hh.mode(vi) == "tag"
    with pytest.raises(CapabilityError):
        hh.peek(vi)
    # plain values just read (origin must merely be readable).
    assert hh.peek_field(outer, "plain") == 42
    # tag origin reads nothing.
    touter = hh.view(outer, "tag")
    with pytest.raises(CapabilityError):
        hh.peek_field(touter, "plain")


def test_heap_plain_int_field_is_data_even_if_it_collides_with_a_handle():
    hh = HostHeap()
    hh.box([9, 9, 9], mode="ref")          # issues handle 1
    o = hh.box({"count": 1}, mode="ref")   # plain int 1, NOT a reference
    assert hh.peek_field(o, "count") == 1  # data, not a view of handle 1


def test_heap_poke_through_writable_view_updates_all_aliases():
    hh = HostHeap()
    r = hh.box({"x": 1}, mode="ref")
    v = hh.view(r, "ref")                  # alias(ref)=ref: writable view
    b = hh.view(r, "box")
    hh.poke(v, {"x": 99})
    assert hh.peek(r) == {"x": 99}         # root sees the write
    assert hh.peek(b) == {"x": 99}         # sibling view sees it too


def test_heap_field_read_never_mints_a_second_owner():
    """Regression (round-5 review): iso▷iso / trn▷trn field reads must
    come back as aliases (tag / box), or two owners could each unbox —
    extracting ownership of one object twice."""
    hh = HostHeap()
    inner = hh.box([1, 2, 3], mode="iso")
    outer = hh.box({"x": HandleRef(inner)}, mode="iso")
    v = hh.peek_field(outer, "x")
    assert hh.mode(v) == "tag"             # alias(iso▷iso) = alias(iso)
    with pytest.raises(CapabilityError):
        hh.unbox(v)                        # no second ownership take
    hh2 = HostHeap()
    t_in = hh2.box({"n": 1}, mode="trn")
    t_out = hh2.box({"y": HandleRef(t_in)}, mode="trn")
    w = hh2.peek_field(t_out, "y")
    assert hh2.mode(w) == "box"            # alias(trn▷trn) = alias(trn)
    with pytest.raises(CapabilityError):
        hh2.poke(w, {})                    # no second writer


def test_heap_freeze_and_recover():
    hh = HostHeap()
    t = hh.box([1], mode="trn")
    assert hh.mode(hh.freeze(t)) == "val"      # trn→val: Pony's freeze
    r = hh.box([2], mode="ref")
    assert hh.mode(hh.recover_iso(r)) == "iso"  # unaliased ref lifts
    r2 = hh.box([3], mode="ref")
    _ = hh.view(r2, "box")
    with pytest.raises(CapabilityError):
        hh.recover_iso(r2)                     # aliased: no lift
    v = hh.box([4], mode="val")
    with pytest.raises(CapabilityError):
        hh.recover_iso(v)                      # shared never unique again
    b = hh.view(hh.box([5], mode="ref"), "box")
    with pytest.raises(CapabilityError):
        hh.freeze(b)                           # borrowed view: no freeze
